"""Closed-loop model maintenance: drift -> background refit -> hot swap.

The drift detector (``obs/drift.py``, fed by the live plane from the
query signals every serving seam already emits) answers "which tenants'
models have gone stale"; this module turns that into action without
touching the serving path:

1. **Trigger** — ``run_maintenance(fleet)`` collects the breached
   tenants from the live plane (or takes an explicit list) and records
   the signal values at the moment of the decision.
2. **Background refit** — one ``sched.submit`` batch re-estimates the
   drifted tenants' params, warm-started from each tenant's CURRENT
   params (``Job(init=...)``).  The jobs carry the tenant's standardized
   panel with ``standardize=False`` models, so the refit params come
   back directly in the slot's frozen standardized scale — swappable
   without any rescaling.  Missing entries are mean-imputed (exact zero
   in the standardized scale) because the batched engine requires fully
   observed panels; the held-out scores below are masked, so imputation
   never contaminates the quality decision.
3. **Quality gate** — before/after held-out one-step prediction error
   (the arXiv 1910.08615 objective): the NumPy f64 oracle filters the
   panel and scores ``y_t - Lam x_pred_t`` over the observed entries of
   the trailing ``holdout_rows`` rows.  One-step predictions at t use
   only data before t, so training through the window is legitimate
   pseudo-out-of-sample scoring.  The swap happens only when the refit
   improves the score by at least ``min_gain``.
4. **Hot swap** — ``fleet.swap_params`` rewrites the tenant's params in
   place through the exact demote/admit shadow round-trip: same
   executable, zero recompiles, bucket-mates bit-identical.  The
   tenant's drift detector is reset (a new regime needs a new healthy
   baseline).
5. **Decision trail** — every phase emits a structured ``maintenance``
   trace event (trigger signals, advisor's engine pick, refit cost,
   quality delta, swap timestamp) that ``record_event`` maps to the
   live-plane counters/gauges (``refits_total``/``swaps_total``/
   ``drift_score``) and ``obs.report`` renders as the per-tenant
   maintenance table.

The engine/rank advisor (``admission.choose_engine``, calibrated +
evidence-gated) is consulted per tenant and its pick recorded; the
in-place swap itself is params-only on the SAME engine — changing the
serving engine would need a new executable (a recompile the serving
budget forbids), so an engine disagreement is surfaced in the trail for
the operator instead of applied silently.

Everything here is host-side and jax-free except the refit dispatches
themselves; nothing runs unless ``run_maintenance`` is called, so the
serving path is bit-identical with maintenance never invoked.
"""

from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["MaintenancePolicy", "MaintenanceRecord", "heldout_score",
           "run_maintenance"]


@dataclasses.dataclass(frozen=True)
class MaintenancePolicy:
    """Knobs for one maintenance pass."""

    holdout_rows: int = 8      # trailing rows scored held-out one-step
    min_gain: float = 0.0      # required score improvement to swap
    max_iters: int = 50        # background refit EM budget
    tol: float = 1e-6          # background refit stop tolerance
    max_buckets: int = 3       # sched.submit bucketing cap
    retune: bool = False       # also re-tune Q/R hypers per tenant
    #                          # (estim.tune gradient search on the
    #                          # tenant's window); the tuned candidate
    #                          # competes with the plain refit on the
    #                          # same held-out gate and lands through the
    #                          # SAME params-only swap seam — zero
    #                          # recompiles, trail action "retune"
    retune_steps: int = 8      # tune search budget (Adam steps)
    retune_em_iters: int = 5   # tune search inner EM budget


@dataclasses.dataclass
class MaintenanceRecord:
    """One tenant's decision-trail row (what the trace events carry)."""

    tenant: str
    trigger: dict              # signal values at the decision
    advice: str                # advisor's engine pick (recorded, not applied)
    engine: str                # the engine actually serving the tenant
    refit_s: float
    refit_iters: int
    score_before: float        # held-out one-step MSE (standardized)
    score_after: float
    quality_delta: float       # score_before - score_after (> 0 == better)
    action: str                # "swap", "retune" (tuned candidate won;
    #                          # policy.retune only) or "skip"
    swap_t: Optional[float]    # perf_counter at swap (None when skipped)
    tune: Optional[dict] = None  # policy.retune only: the tune record
    #                          # (q_scale/r_scale/lam_ridge + held-out
    #                          # curve) — recorded even when the plain
    #                          # refit wins


def heldout_score(Y_std: np.ndarray, W: Optional[np.ndarray], params,
                  holdout_rows: int) -> float:
    """Held-out one-step prediction error (standardized units).

    Runs the NumPy f64 oracle filter over the panel and scores the
    one-step predictions ``Lam x_pred_t`` against the realized rows over
    the observed entries of the trailing ``holdout_rows`` rows — the
    "fitting a Kalman smoother to data" quality objective.  Lower is
    better; NaN when the window holds no observed entries.

    The actual reduction lives in ``estim.score`` — ONE definition shared
    with ``estim.tune``'s in-graph objective and ``oos_evaluate``.
    """
    from ..estim.score import heldout_mse_np
    return heldout_mse_np(Y_std, W, params, holdout_rows)


def _emit(ev: dict) -> None:
    """One maintenance trace event: to the active tracer (which forwards
    to the live plane) or straight to the plane when untraced."""
    from ..obs.trace import current_tracer
    tr = current_tracer()
    if tr is not None:
        tr.emit("maintenance", **{k: v for k, v in ev.items()
                                  if k not in ("t", "kind")})
    else:
        from ..obs.live import observe
        observe(ev)


def run_maintenance(fleet, tenants: Optional[Sequence[str]] = None, *,
                    policy: Optional[MaintenancePolicy] = None,
                    backend: str = "tpu",
                    runs: Optional[str] = None) -> List[MaintenanceRecord]:
    """One maintenance pass over ``fleet``: refit + conditionally swap.

    ``tenants=None`` takes the live plane's currently-breached drift
    detectors (restricted to this fleet's tenants); pass an explicit
    list to force a pass.  Returns one :class:`MaintenanceRecord` per
    tenant processed (empty when nothing drifted).  Serving ticks are
    untouched: refits run as a separate background ``sched.submit``
    batch and land through the in-place params swap seam.
    """
    from ..obs.live import plane as _plane
    from ..sched import Job, submit
    from .admission import choose_engine
    policy = policy if policy is not None else MaintenancePolicy()
    pl = _plane()
    if tenants is None:
        tenants = [t for t in pl.drift_status()["breached"]
                   if t in fleet._slot_of]
    tenants = list(tenants)
    if not tenants:
        return []

    jobs, ctx = [], []
    for name in tenants:
        if name not in fleet._slot_of:
            raise KeyError(f"unknown tenant {name!r} (fleet has "
                           f"{sorted(fleet._slot_of)})")
        bucket, slot = fleet._slot_of[name]
        Y = np.asarray(slot.Y_orig, np.float64)
        W = np.asarray(slot.W_orig, np.float64)
        Yz = slot.std.transform(Y) if slot.std is not None else Y
        # Mean imputation in the standardized scale (exact zeros) — the
        # batched refit engine needs fully-observed panels; the held-out
        # scores below stay masked to truly observed entries.
        Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
        p_cur = fleet._slot_params_np(bucket, slot)
        before = heldout_score(Yz, W, p_cur, policy.holdout_rows)
        engine = bucket.cfg.filter if not slot.quarantined else \
            slot.evicted._cfg.filter
        advice = choose_engine(
            (Y.shape[0], slot.N, slot.k), policy.max_iters,
            rank=int(bucket.cfg.rank), runs=runs)
        det = pl.drift_state(name)
        trigger = dict((det or {}).get("last", {}))
        trigger["drift_score"] = float((det or {}).get("drift_score", 0.0))
        _emit({"t": time.perf_counter(), "kind": "maintenance",
               "session": fleet.fleet_id, "tenant": name,
               "action": "trigger", "engine": engine, "advice": advice,
               **{k: round(float(v), 6) for k, v in trigger.items()}})
        model = dataclasses.replace(slot.model, standardize=False)
        jobs.append(Job(Y=Yz, model=model, tenant=name, init=p_cur,
                        max_iters=policy.max_iters, tol=policy.tol))
        ctx.append((name, bucket, slot, Yz, W, before, engine, advice,
                    trigger))

    stats: dict = {}
    results = submit(jobs, backend=backend,
                     max_buckets=policy.max_buckets, stats=stats)

    records: List[MaintenanceRecord] = []
    for res, (name, bucket, slot, Yz, W, before, engine, advice,
              trigger) in zip(results, ctx):
        p_new = res.fit.params
        after = heldout_score(Yz, W, p_new, policy.holdout_rows)
        delta = (before - after if np.isfinite(before)
                 and np.isfinite(after) else float("nan"))
        _emit({"t": time.perf_counter(), "kind": "maintenance",
               "session": fleet.fleet_id, "tenant": name,
               "action": "refit", "refit_s": float(res.compute_s),
               "n_iters": int(res.fit.n_iters),
               "converged": bool(res.fit.converged),
               "engine": engine, "advice": advice})
        # Optional hyper re-tune (policy.retune): a small gradient search
        # (estim.tune) warm-started from the refit params.  Its best fit
        # competes with the plain refit on the SAME masked held-out gate;
        # the winner lands through the SAME params-only swap seam (zero
        # recompiles) and the trail records the chosen hypers either way.
        tune_rec = None
        p_swap = p_new
        action = "swap"
        if policy.retune:
            from ..estim.em import EMConfig
            from ..estim.tune import TuneOptions, tune_fit
            model = slot.model
            tune_rec = tune_fit(
                Yz, W, p_new,
                EMConfig(estimate_A=model.estimate_A,
                         estimate_Q=model.estimate_Q,
                         estimate_init=model.estimate_init, filter="info"),
                TuneOptions(method="grad", steps=policy.retune_steps,
                            em_iters=policy.retune_em_iters,
                            holdout_rows=policy.holdout_rows),
                return_params=True)
            p_tuned = tune_rec.pop("best_params", None)
            if p_tuned is not None:
                after_tuned = heldout_score(Yz, W, p_tuned,
                                            policy.holdout_rows)
                if np.isfinite(after_tuned) and (
                        not np.isfinite(after) or after_tuned < after):
                    p_swap = p_tuned
                    after = after_tuned
                    delta = (before - after if np.isfinite(before)
                             else float("nan"))
                    action = "retune"
        do_swap = bool(np.isfinite(delta) and delta >= policy.min_gain)
        swap_t = None
        if do_swap:
            fleet.swap_params(name, p_swap)
            pl.reset_drift(name)
            swap_t = time.perf_counter()
        hyp = ({} if tune_rec is None else
               {"q_scale": round(float(tune_rec["q_scale"]), 6),
                "r_scale": round(float(tune_rec["r_scale"]), 6),
                "lam_ridge": round(float(tune_rec["lam_ridge"]), 6)})
        _emit({"t": swap_t if swap_t is not None else time.perf_counter(),
               "kind": "maintenance", "session": fleet.fleet_id,
               "tenant": name, "action": action if do_swap else "skip",
               "quality_delta": (round(delta, 9) if np.isfinite(delta)
                                 else None),
               "score_before": (round(before, 9) if np.isfinite(before)
                                else None),
               "score_after": (round(after, 9) if np.isfinite(after)
                               else None),
               "engine": engine, "advice": advice, **hyp})
        records.append(MaintenanceRecord(
            tenant=name, trigger=trigger, advice=advice, engine=engine,
            refit_s=float(res.compute_s), refit_iters=int(res.fit.n_iters),
            score_before=float(before), score_after=float(after),
            quality_delta=float(delta), action=action if do_swap
            else "skip", swap_t=swap_t, tune=tune_rec))
    return records

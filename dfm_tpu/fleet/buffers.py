"""Device-resident fleet state: tenant slots packed into bucket buffers.

A ``TenantSlot`` is the host-side record of one tenant: its frozen
standardizer, model, live length, budgets, and the ORIGINAL-UNITS live
panel (the eviction seed — a quarantined tenant is rebuilt as a lone
``NowcastSession`` from exactly this state).  A ``FleetBucket`` packs B
slots of one capacity class into (B, T_cap, N_max)-shaped device panel
buffers plus one stacked params pytree, built with the PR 8 inert-padding
seams (``pad_panel_to_t``/``pad_panel_to_n`` exact-zero panels,
``pad_params_to_k``/``pad_params_to_n`` inert factors/series) — so lane b
of the bucket IS tenant b's lone session buffer, bit-for-bit, under the
masked serving twins.

Host shadows (f64 numpy panels + per-lane cpu_ref params) mirror the
device state exactly, serving the same two roles they do in
``serve/session.py``: the donated-retry rebuild source (``_redeploy``)
and the quarantine/eviction seed.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..estim.batched import (pad_panel_to_n, pad_panel_to_t, pad_params_to_k,
                             pad_params_to_n, stack_params, unstack_params)
from ..estim.em import EMConfig, noise_floor_for
from ..obs.trace import shape_key
from ..ops.precision import accum_dtype
from ..serve.batched import FleetOptions
from ..ssm.params import SSMParams as JaxParams
from ..utils.data import build_mask

__all__ = ["TenantSlot", "FleetBucket"]


@dataclasses.dataclass
class TenantSlot:
    """Host record of one fleet tenant (see module docstring)."""

    name: str
    lane: int                  # index along the bucket's batch axis
    N: int
    k: int
    t: int                     # live panel length (rows so far)
    capacity: int              # this tenant's own row budget (<= T_cap)
    max_iters: int
    tol: float
    std: object                # frozen Standardizer (or None)
    model: object              # DynamicFactorModel
    Y_orig: np.ndarray         # (t, N) live panel, ORIGINAL units, NaNs
    W_orig: np.ndarray         # (t, N) {0,1} observation mask
    quarantined: bool = False
    div_run: int = 0           # consecutive diverged ticks (escalation)
    n_queries: int = 0
    evicted: Optional[object] = None   # lone NowcastSession after eviction

    def append_orig(self, rows: np.ndarray, W_rows: np.ndarray):
        """Track an accepted update in original units (eviction seed)."""
        self.Y_orig = np.concatenate([self.Y_orig, rows], axis=0)
        self.W_orig = np.concatenate([self.W_orig, W_rows], axis=0)
        self.t += rows.shape[0]


class FleetBucket:
    """One capacity class: B tenants resident in batched device buffers.

    ``entries`` is a list of ``(name, res, Y, mask, capacity, max_iters,
    tol)`` tuples; ``dims = (T_cap, N_max, k_max)`` the class shape every
    member is padded to.  ``pad_lanes`` appends that many FILLER lanes
    (copies of lane 0, permanently ``tick_act=False``) so the batch axis
    divides a mesh — value-inert by the freeze algebra.
    """

    def __init__(self, entries, dims, *, r_max: int, backend, opts,
                 pad_lanes: int = 0):
        T_cap, N_max, k_max = dims
        self.dims = dims
        self.r_max = int(r_max)
        self.opts = opts
        self.backend = backend
        self.dt = backend._dtype()
        self.acc = accum_dtype(self.dt)
        self.slots: List[TenantSlot] = []
        Yh, Wh, ps = [], [], []
        est = None
        for lane, (name, res, Y, mask, cap, m_it, tol) in enumerate(entries):
            Y = np.asarray(Y, dtype=np.float64)
            T0, N = Y.shape
            W = build_mask(Y, mask)
            std = res.standardizer
            Yz = std.transform(Y) if std is not None else Y
            Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
            Yh.append(pad_panel_to_t(pad_panel_to_n(Yz, N_max), T_cap))
            Wh.append(pad_panel_to_t(pad_panel_to_n(W, N_max), T_cap))
            k = res.params.Lam.shape[1]
            ps.append(pad_params_to_n(pad_params_to_k(res.params, k_max),
                                      N_max))
            m = res.model
            e = (m.estimate_A, m.estimate_Q, m.estimate_init)
            if est is None:
                est = e
            elif e != est:   # admission groups by config; belt-and-braces
                raise ValueError(
                    f"tenant {name!r} has estimation flags {e} but the "
                    f"bucket was planned for {est}")
            self.slots.append(TenantSlot(
                name=name, lane=lane, N=N, k=k, t=T0, capacity=int(cap),
                max_iters=int(m_it), tol=float(tol), std=std, model=m,
                Y_orig=Y.copy(), W_orig=W.copy()))
        for _ in range(int(pad_lanes)):     # frozen mesh-filler lanes
            Yh.append(Yh[0].copy())
            Wh.append(Wh[0].copy())
            ps.append(ps[0])
        self.B = len(Yh)
        self.Yhost = np.stack(Yh).astype(np.float64)
        self.Whost = np.stack(Wh).astype(np.float64)
        self.p_host = ps                      # padded cpu_ref params, f64
        # One static iteration cap per bucket (the scan length — per-lane
        # budgets ride the traced iter_cap vector below it).
        self.max_iters = max(s.max_iters for s in self.slots)
        self.cfg = EMConfig(estimate_A=est[0], estimate_Q=est[1],
                            estimate_init=est[2], filter="info", debug=False)
        with backend._precision_ctx():
            self.Ybuf = jnp.asarray(self.Yhost, self.dt)
            self.Wbuf = jnp.asarray(self.Whost, self.dt)
            self.p = stack_params(self.p_host, dtype=self.dt)
        self.key = shape_key(self.Ybuf, "info", f"rows{self.r_max}",
                             f"max{self.max_iters}", f"fleetB{self.B}")
        self.n_ticks = 0

    # -- per-tick traced vectors ---------------------------------------
    def floor_for(self, slot: TenantSlot, t_new: int) -> float:
        """Per-tenant ABSOLUTE loglik noise floor at the TRUE live size —
        the exact float the same tenant's lone session would compute."""
        return float(noise_floor_for(self.dt, t_new * slot.N,
                                     mult=self.cfg.noise_floor_mult))

    # -- self-healing --------------------------------------------------
    def redeploy(self):
        """Rebuild device state from the host shadows (donated-retry
        path: a failed donated dispatch consumed the buffers).  The
        shadows hold the exact f64 values originally uploaded, so the
        cast reproduces the device state bit-for-bit."""
        with self.backend._precision_ctx():
            self.Ybuf = jnp.asarray(self.Yhost, self.dt)
            self.Wbuf = jnp.asarray(self.Whost, self.dt)
            self.p = stack_params(self.p_host, dtype=self.dt)

    def rebind(self, out):
        """Adopt a tick's output buffers as the resident state."""
        self.Ybuf, self.Wbuf = out["Ybuf"], out["Wbuf"]
        self.p = out["p"]

    def params_host(self, out_p: Optional[JaxParams] = None):
        """Per-lane padded cpu_ref params from a (possibly fresh) stacked
        pytree — one small d2h when reading the resident params."""
        return unstack_params(out_p if out_p is not None else self.p)

    def __repr__(self):
        T, N, k = self.dims
        return (f"FleetBucket(B={self.B}, T_cap={T}, N_max={N}, "
                f"k_max={k}, {len(self.slots)} tenants)")


# Re-exported for driver convenience (the jitted statics live with the
# core in serve/batched.py).
_ = FleetOptions

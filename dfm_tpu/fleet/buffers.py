"""Device-resident fleet state: tenant slots packed into bucket buffers.

A ``TenantSlot`` is the host-side record of one tenant: its frozen
standardizer, model, live length, budgets, and the ORIGINAL-UNITS live
panel (the eviction seed — a quarantined tenant is rebuilt as a lone
``NowcastSession`` from exactly this state).  A ``FleetBucket`` packs B
slots of one capacity class into (B, T_cap, N_max)-shaped device panel
buffers plus one stacked params pytree, built with the PR 8 inert-padding
seams (``pad_panel_to_t``/``pad_panel_to_n`` exact-zero panels,
``pad_params_to_k``/``pad_params_to_n`` inert factors/series) — so lane b
of the bucket IS tenant b's lone session buffer, bit-for-bit, under the
masked serving twins.

Host shadows (f64 numpy panels + per-lane cpu_ref params) mirror the
device state exactly, serving the same two roles they do in
``serve/session.py``: the donated-retry rebuild source (``_redeploy``)
and the quarantine/eviction seed.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..estim.batched import (pad_panel_to_n, pad_panel_to_t, pad_params_to_k,
                             pad_params_to_n, stack_params, unstack_params)
from ..estim.em import EMConfig, noise_floor_for
from ..obs.trace import shape_key
from ..ops.precision import accum_dtype
from ..serve.batched import FleetOptions
from ..ssm.params import SSMParams as JaxParams
from ..utils.data import build_mask

__all__ = ["TenantSlot", "FleetBucket"]


@dataclasses.dataclass
class TenantSlot:
    """Host record of one fleet tenant (see module docstring).

    Tiering (PR 14): ``tier`` is "hot" (device-resident lane), "warm"
    (lane freed; the exact padded host shadows parked in ``warm_Y`` /
    ``warm_W`` / ``warm_p``) or "cold" (shadows spilled to an on-disk
    npz at ``cold_path``).  A warm/cold slot has ``lane is None``; re-
    admission restores the shadows into a free lane bit-for-bit.
    """

    name: str
    lane: Optional[int]        # index along the bucket's batch axis
    N: int
    k: int
    t: int                     # live panel length (rows so far)
    capacity: int              # this tenant's own row budget (<= T_cap)
    max_iters: int
    tol: float
    std: object                # frozen Standardizer (or None)
    model: object              # DynamicFactorModel
    Y_orig: np.ndarray         # (t, N) live panel, ORIGINAL units, NaNs
    W_orig: np.ndarray         # (t, N) {0,1} observation mask
    quarantined: bool = False
    div_run: int = 0           # consecutive diverged ticks (escalation)
    n_queries: int = 0
    evicted: Optional[object] = None   # lone NowcastSession after eviction
    t_total: int = 0           # stream position: rows EVER held
    tier: str = "hot"
    warm_Y: Optional[np.ndarray] = None   # (T_cap, N_max) parked shadow
    warm_W: Optional[np.ndarray] = None
    warm_p: Optional[object] = None       # padded cpu_ref params (f64)
    cold_path: Optional[str] = None
    last_used: int = 0         # LRU stamp (fleet submit sequence)
    last_band: Optional[tuple] = None  # (y_fore, y_sd) of previous query

    @property
    def n_evicted(self) -> int:
        """Rows retired by the ring buffer so far (0 outside ring mode)."""
        return self.t_total - self.t

    def append_orig(self, rows: np.ndarray, W_rows: np.ndarray):
        """Track an accepted update in original units (eviction seed)."""
        self.Y_orig = np.concatenate([self.Y_orig, rows], axis=0)
        self.W_orig = np.concatenate([self.W_orig, W_rows], axis=0)
        self.t += rows.shape[0]
        self.t_total += rows.shape[0]

    def evict_orig(self, n_evict: int):
        """Drop the oldest ``n_evict`` rows of the original-units seed —
        the host mirror of the in-graph ring eviction, keeping the
        quarantine/snapshot seed bounded at the trailing window."""
        if n_evict <= 0:
            return
        self.Y_orig = self.Y_orig[n_evict:]
        self.W_orig = self.W_orig[n_evict:]
        self.t -= n_evict


class FleetBucket:
    """One capacity class: B tenants resident in batched device buffers.

    ``entries`` is a list of ``(name, res, Y, mask, capacity, max_iters,
    tol)`` tuples; ``dims = (T_cap, N_max, k_max)`` the class shape every
    member is padded to.  ``pad_lanes`` appends that many FILLER lanes
    (copies of lane 0, permanently ``tick_act=False``) so the batch axis
    divides a mesh — value-inert by the freeze algebra.

    ``lanes`` (default: every member) caps the RESIDENT lane count: the
    first ``lanes`` members start hot, the rest start WARM — their padded
    shadows parked on the slot, no device footprint — and page in on
    demand via :meth:`admit` (``driver.SessionFleet`` chooses victims
    with the calibrated paging economics).  ``lane_of`` maps a device
    lane to its current occupant (free lanes absent).
    """

    def __init__(self, entries, dims, *, r_max: int, backend, opts,
                 pad_lanes: int = 0, lanes: Optional[int] = None,
                 filter: str = "info", rank: int = 0):
        T_cap, N_max, k_max = dims
        self.dims = dims
        self.r_max = int(r_max)
        self.opts = opts
        self.backend = backend
        self.dt = backend._dtype()
        self.acc = accum_dtype(self.dt)
        self.slots: List[TenantSlot] = []
        n_hot = len(entries) if lanes is None else max(1, int(lanes))
        Yh, Wh, ps = [], [], []
        est = None
        for i, (name, res, Y, mask, cap, m_it, tol) in enumerate(entries):
            Y = np.asarray(Y, dtype=np.float64)
            T0, N = Y.shape
            W = build_mask(Y, mask)
            std = res.standardizer
            Yz = std.transform(Y) if std is not None else Y
            Yz = np.where(W > 0, np.nan_to_num(Yz), 0.0)
            Yp = pad_panel_to_t(pad_panel_to_n(Yz, N_max), T_cap)
            Wp = pad_panel_to_t(pad_panel_to_n(W, N_max), T_cap)
            k = res.params.Lam.shape[1]
            pp = pad_params_to_n(pad_params_to_k(res.params, k_max), N_max)
            m = res.model
            e = (m.estimate_A, m.estimate_Q, m.estimate_init)
            if est is None:
                est = e
            elif e != est:   # admission groups by config; belt-and-braces
                raise ValueError(
                    f"tenant {name!r} has estimation flags {e} but the "
                    f"bucket was planned for {est}")
            slot = TenantSlot(
                name=name, lane=None, N=N, k=k, t=T0, capacity=int(cap),
                max_iters=int(m_it), tol=float(tol), std=std, model=m,
                Y_orig=Y.copy(), W_orig=W.copy(), t_total=T0)
            if i < n_hot:
                slot.lane = len(Yh)
                Yh.append(Yp)
                Wh.append(Wp)
                ps.append(pp)
            else:           # over-subscribed: park the shadows, no lane
                slot.tier = "warm"
                slot.warm_Y = np.asarray(Yp, np.float64)
                slot.warm_W = np.asarray(Wp, np.float64)
                slot.warm_p = pp
            self.slots.append(slot)
        for _ in range(int(pad_lanes)):     # frozen mesh-filler lanes
            Yh.append(Yh[0].copy())
            Wh.append(Wh[0].copy())
            ps.append(ps[0])
        self.B = len(Yh)
        self.n_lanes = self.B - int(pad_lanes)   # tenant-usable lanes
        self.lane_of = {s.lane: s for s in self.slots if s.lane is not None}
        self.free_lanes: List[int] = []
        self.Yhost = np.stack(Yh).astype(np.float64)
        self.Whost = np.stack(Wh).astype(np.float64)
        self.p_host = ps                      # padded cpu_ref params, f64
        # One static iteration cap per bucket (the scan length — per-lane
        # budgets ride the traced iter_cap vector below it).
        self.max_iters = max(s.max_iters for s in self.slots)
        # Engine routing (PR 17): the bucket's whole serving program —
        # warm EM, final smooth, bands — runs this filter; rank rides
        # only with lowrank so info buckets' EMConfig (and executable
        # cache keys) equal the pre-routing ones bit-for-bit.
        rank = int(rank) if filter == "lowrank" else 0
        self.cfg = EMConfig(estimate_A=est[0], estimate_Q=est[1],
                            estimate_init=est[2], filter=str(filter),
                            rank=rank, debug=False)
        with backend._precision_ctx():
            self.Ybuf = jnp.asarray(self.Yhost, self.dt)
            self.Wbuf = jnp.asarray(self.Whost, self.dt)
            self.p = stack_params(self.p_host, dtype=self.dt)
        self.key = shape_key(
            self.Ybuf, self.cfg.filter,
            *((f"rank{rank}",) if self.cfg.filter == "lowrank" else ()),
            f"rows{self.r_max}", f"max{self.max_iters}", f"fleetB{self.B}")
        self.n_ticks = 0

    # -- per-tick traced vectors ---------------------------------------
    def floor_for(self, slot: TenantSlot, t_new: int) -> float:
        """Per-tenant ABSOLUTE loglik noise floor at the TRUE live size —
        the exact float the same tenant's lone session would compute."""
        return float(noise_floor_for(self.dt, t_new * slot.N,
                                     mult=self.cfg.noise_floor_mult))

    # -- self-healing --------------------------------------------------
    def redeploy(self):
        """Rebuild device state from the host shadows (donated-retry
        path: a failed donated dispatch consumed the buffers).  The
        shadows hold the exact f64 values originally uploaded, so the
        cast reproduces the device state bit-for-bit."""
        with self.backend._precision_ctx():
            self.Ybuf = jnp.asarray(self.Yhost, self.dt)
            self.Wbuf = jnp.asarray(self.Whost, self.dt)
            self.p = stack_params(self.p_host, dtype=self.dt)

    def rebind(self, out):
        """Adopt a tick's output buffers as the resident state."""
        self.Ybuf, self.Wbuf = out["Ybuf"], out["Wbuf"]
        self.p = out["p"]

    def params_host(self, out_p: Optional[JaxParams] = None):
        """Per-lane padded cpu_ref params from a (possibly fresh) stacked
        pytree — one small d2h when reading the resident params."""
        return unstack_params(out_p if out_p is not None else self.p)

    # -- snapshot tiering ----------------------------------------------
    def demote(self, slot: TenantSlot):
        """Hot -> warm: park the tenant's exact device state on the slot
        and free its lane.  One small params d2h (the f64 read is an
        exact representation of the device values, so a later
        :meth:`admit` reproduces them bit-for-bit); the lane's stale
        device data stays behind, value-inert under the tick freezes."""
        ln = slot.lane
        # Refresh the params shadows from the device first: outside the
        # guarded donated path p_host lags the resident params.
        self.p_host = self.params_host()
        slot.warm_Y = self.Yhost[ln].copy()
        slot.warm_W = self.Whost[ln].copy()
        slot.warm_p = self.p_host[ln]
        slot.lane = None
        slot.tier = "warm"
        del self.lane_of[ln]
        self.free_lanes.append(ln)
        self.free_lanes.sort()

    def admit(self, slot: TenantSlot) -> int:
        """Warm -> hot: restore the parked shadows into a free lane and
        redeploy the bucket.  Costs one params d2h (bucket-mates' shadow
        refresh — without it the full-bucket re-upload would roll them
        back) + the bucket h2d; the re-admitted tenant's device state is
        bit-identical to its never-evicted twin's."""
        if not self.free_lanes:
            raise RuntimeError("bucket has no free lane (driver bug: "
                               "admit() needs a demote first)")
        ln = self.free_lanes.pop(0)
        self.p_host = self.params_host()
        self.Yhost[ln] = slot.warm_Y
        self.Whost[ln] = slot.warm_W
        self.p_host[ln] = slot.warm_p
        self.redeploy()
        slot.lane = ln
        slot.tier = "hot"
        slot.warm_Y = slot.warm_W = slot.warm_p = None
        slot.cold_path = None
        self.lane_of[ln] = slot
        return ln

    def __repr__(self):
        T, N, k = self.dims
        return (f"FleetBucket(B={self.B}, T_cap={T}, N_max={N}, "
                f"k_max={k}, {len(self.slots)} tenants)")


# Re-exported for driver convenience (the jitted statics live with the
# core in serve/batched.py).
_ = FleetOptions

"""Fleet serving: batched session multiplexing across tenants.

``open_fleet(results, panels)`` keeps B tenants' params + capacity-padded
panels device-resident in shape-bucketed batched buffers (admission
control assigns tenants to capacity classes via the calibrated cost
model); ``fleet.submit(tenant, rows)`` enqueues and ``fleet.drain()``
serves the queue as ONE fused batched ``serve_update`` program per bucket
per tick — ragged per-tenant appends, independent warm EM freezes, RTS
smooth, nowcast + forecasts — with at most one blocking d2h per tick,
one executable per bucket shape, and per-tenant answers numerically
pinned to the same tenant's lone ``NowcastSession``.  Ticks run under the
PR 10 dispatch guard with per-tenant quarantine: a poisoned tenant is
evicted to a lone guarded session without stalling its bucket-mates.
"""

from .admission import ClassAssignment, fleet_pad_waste, plan_admission
from .buffers import FleetBucket, TenantSlot
from .driver import SessionFleet, open_fleet, read_manifest, restore_fleet
from .maintenance import (MaintenancePolicy, MaintenanceRecord,
                          heldout_score, run_maintenance)

__all__ = ["SessionFleet", "open_fleet", "restore_fleet", "read_manifest",
           "FleetBucket", "TenantSlot", "ClassAssignment",
           "plan_admission", "fleet_pad_waste", "MaintenancePolicy",
           "MaintenanceRecord", "heldout_score", "run_maintenance"]

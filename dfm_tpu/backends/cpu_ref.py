"""CPU reference backend: NumPy float64 Kalman/EM for dynamic factor models.

This module is the correctness oracle for the whole framework (SURVEY.md section
7.1 M0).  The reference package ``joidegn/DynamicFactorModels.jl`` could not be
mounted (its directory is empty — SURVEY.md section 0), so the operative spec is
BASELINE.json:5, which pins the exact recursions implemented here:

    predict:  f_t|t-1 = A f_{t-1},      P_t|t-1 = A P_{t-1} A' + Q
    update:   S_t = Lam P_t|t-1 Lam' + R,  K_t = P_t|t-1 Lam' S_t^{-1}
    smoother: RTS backward pass with lag-one covariances for the EM M-step.

Model (SURVEY.md section 3 notation):

    y_t = Lam f_t + eps_t,   eps_t ~ N(0, diag(R))       (observation, N series)
    f_t = A f_{t-1} + eta_t, eta_t ~ N(0, Q)             (state, k factors)
    f_1 ~ N(mu0, P0)

Missing observations are handled by a {0,1} mask W (T, N): masked rows are
excluded from the update and the log-likelihood (Banbura-Modugno, SURVEY.md
section 3.4).  A fully-observed mask must reproduce the dense path exactly —
that equivalence is a unit test.

Everything here is float64 NumPy, deliberately simple and allocation-heavy; it
exists to be *right*, not fast.  The JAX/TPU backend is validated against this
module to 1e-5 in log-likelihood (BASELINE.json:5).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

__all__ = [
    "SSMParams",
    "KalmanResult",
    "SmootherResult",
    "kalman_filter",
    "kalman_filter_info",
    "rts_smoother",
    "em_step",
    "em_fit",
    "pca_init",
    "forecast",
]


@dataclasses.dataclass
class SSMParams:
    """Dense state-space parameters (the pytree mirrored by the JAX backend).

    Lam : (N, k) factor loadings
    A   : (k, k) factor VAR(1) transition (zero matrix for a static DFM)
    Q   : (k, k) state innovation covariance
    R   : (N,)   diagonal observation noise variances
    mu0 : (k,)   initial state mean
    P0  : (k, k) initial state covariance
    """

    Lam: np.ndarray
    A: np.ndarray
    Q: np.ndarray
    R: np.ndarray
    mu0: np.ndarray
    P0: np.ndarray

    def copy(self) -> "SSMParams":
        return SSMParams(*(np.array(getattr(self, f.name), dtype=np.float64)
                           for f in dataclasses.fields(self)))

    @property
    def n_series(self) -> int:
        return self.Lam.shape[0]

    @property
    def n_factors(self) -> int:
        return self.Lam.shape[1]


@dataclasses.dataclass
class KalmanResult:
    x_pred: np.ndarray  # (T, k)   f_t|t-1
    P_pred: np.ndarray  # (T, k, k)
    x_filt: np.ndarray  # (T, k)   f_t|t
    P_filt: np.ndarray  # (T, k, k)
    loglik: float


@dataclasses.dataclass
class SmootherResult:
    x_sm: np.ndarray   # (T, k)    E[f_t | y_1..T]
    P_sm: np.ndarray   # (T, k, k) Cov[f_t | y_1..T]
    P_lag: np.ndarray  # (T, k, k) Cov[f_t, f_{t-1} | y_1..T]; row 0 is zeros


def _sym(M: np.ndarray) -> np.ndarray:
    return 0.5 * (M + np.swapaxes(M, -1, -2))


def kalman_filter(Y: np.ndarray, p: SSMParams,
                  mask: Optional[np.ndarray] = None) -> KalmanResult:
    """Forward Kalman filter with exact log-likelihood.

    Y    : (T, N) panel; entries at masked positions are ignored (may be nan —
           they are zeroed internally so arithmetic stays finite).
    mask : optional (T, N) {0,1}; 1 = observed.  None means fully observed.

    Uses the Joseph-form covariance update for numerical symmetry/PSD-ness
    (SURVEY.md section 7.2 item 1).  t=1 uses (mu0, P0) directly as the
    prediction, i.e. P0 is the prior on f_1 itself.
    """
    Y = np.asarray(Y, dtype=np.float64)
    T, N = Y.shape
    k = p.n_factors
    Lam, A, Q, R = (np.asarray(p.Lam, np.float64), np.asarray(p.A, np.float64),
                    np.asarray(p.Q, np.float64), np.asarray(p.R, np.float64))
    if mask is not None:
        mask = np.asarray(mask, dtype=np.float64)
        Y = np.where(mask > 0, np.nan_to_num(Y), 0.0)

    x_pred = np.zeros((T, k))
    P_pred = np.zeros((T, k, k))
    x_filt = np.zeros((T, k))
    P_filt = np.zeros((T, k, k))
    loglik = 0.0
    log2pi = np.log(2.0 * np.pi)

    x, P = np.asarray(p.mu0, np.float64), np.asarray(p.P0, np.float64)
    for t in range(T):
        if t > 0:
            x = A @ x_filt[t - 1]
            P = _sym(A @ P_filt[t - 1] @ A.T + Q)
        x_pred[t] = x
        P_pred[t] = P

        if mask is None:
            obs = np.ones(N, dtype=bool)
        else:
            obs = mask[t] > 0
        n_t = int(obs.sum())
        if n_t == 0:
            x_filt[t] = x
            P_filt[t] = P
            continue

        H = Lam[obs]                      # (n_t, k)
        r = R[obs]                        # (n_t,)
        v = Y[t, obs] - H @ x             # innovation
        S = H @ P @ H.T + np.diag(r)      # (n_t, n_t)
        S = _sym(S)
        # Solve via Cholesky — never form S^{-1} explicitly.
        L = np.linalg.cholesky(S)
        Sinv_v = np.linalg.solve(L.T, np.linalg.solve(L, v))
        K = np.linalg.solve(L.T, np.linalg.solve(L, H @ P)).T  # P H' S^-1, (k, n_t)
        x = x + K @ v
        IKH = np.eye(k) - K @ H
        P = _sym(IKH @ P @ IKH.T + (K * r) @ K.T)  # Joseph form
        x_filt[t] = x
        P_filt[t] = P
        loglik += -0.5 * (n_t * log2pi + 2.0 * np.sum(np.log(np.diag(L)))
                          + v @ Sinv_v)

    return KalmanResult(x_pred, P_pred, x_filt, P_filt, float(loglik))


def kalman_filter_info(Y: np.ndarray, p: SSMParams,
                       mask: Optional[np.ndarray] = None) -> KalmanResult:
    """Information-form filter: k x k recursion, N only in matmul reductions.

    NumPy mirror of ``dfm_tpu.ssm.info_filter`` (same algebra: Cholesky of
    I + L'CL, determinant-lemma logdet, residual-pass Woodbury quadratic).
    This is the honest single-threaded CPU baseline at the 10k-series
    headline shape (BASELINE.json:2) where the dense O(N^3)-per-step filter
    is infeasible, and the at-scale golden for the TPU info path.
    Requires diagonal R (always true in this framework).
    """
    Y = np.asarray(Y, dtype=np.float64)
    T, N = Y.shape
    k = p.n_factors
    Lam, A, Q, R = (np.asarray(p.Lam, np.float64), np.asarray(p.A, np.float64),
                    np.asarray(p.Q, np.float64), np.asarray(p.R, np.float64))
    Rinv = 1.0 / R
    logR = np.log(R)
    G = Lam * Rinv[:, None]                       # R^{-1} Lam
    if mask is None:
        B = Y @ G                                 # (T, k)
        C_static = Lam.T @ G
        n_t_all = np.full(T, float(N))
        ldR_all = np.full(T, logR.sum())
    else:
        W = np.asarray(mask, dtype=np.float64)
        Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)
        Y = Yz
        B = Yz @ G
        n_t_all = W.sum(axis=1)
        ldR_all = W @ logR

    I_k = np.eye(k)
    x_pred = np.zeros((T, k))
    P_pred = np.zeros((T, k, k))
    x_filt = np.zeros((T, k))
    P_filt = np.zeros((T, k, k))
    logdetG = np.zeros(T)
    x, P = np.asarray(p.mu0, np.float64), np.asarray(p.P0, np.float64)
    for t in range(T):
        if t > 0:
            x = A @ x_filt[t - 1]
            P = _sym(A @ P_filt[t - 1] @ A.T + Q)
        x_pred[t] = x
        P_pred[t] = P
        if mask is None:
            C = C_static
        else:
            C = (Lam * (W[t] * Rinv)[:, None]).T @ Lam
        Lp = np.linalg.cholesky(P + 1e-12 * I_k)
        Gm = I_k + Lp.T @ C @ Lp
        Lg = np.linalg.cholesky(Gm)
        Pf = Lp @ np.linalg.solve(Lg.T, np.linalg.solve(Lg, Lp.T))
        Pf = _sym(Pf)
        u = B[t] - C @ x
        x = x + Pf @ u
        P = Pf
        x_filt[t] = x
        P_filt[t] = P
        logdetG[t] = 2.0 * np.sum(np.log(np.diag(Lg)))
    # Residual-pass quadratic (cancellation-free; matches the JAX path).
    V = Y - x_pred @ Lam.T
    if mask is not None:
        V = W * V
    VR = V * Rinv[None, :]
    quad_R = np.einsum("tn,tn->t", V, VR)
    U = VR @ Lam
    quad = quad_R - np.einsum("tk,tkl,tl->t", U, P_filt, U)
    log2pi = np.log(2.0 * np.pi)
    loglik = float(np.sum(-0.5 * (n_t_all * log2pi + ldR_all + logdetG
                                  + quad)))
    return KalmanResult(x_pred, P_pred, x_filt, P_filt, loglik)


def resolve_rank(k: int, rank: int = 0) -> int:
    """Shared rank convention for the low-rank engines (mirrored by
    ``ssm.lowrank_filter``): ``rank<=0`` means auto — min(k, 8), the
    largest rank whose triangular work stays in unrolled VPU form
    (``ops.linalg.UNROLL_K_MAX``); explicit ranks clamp to [1, k]."""
    r = int(rank)
    if r <= 0:
        r = min(k, 8)
    return max(1, min(r, k))


def _lowrank_basis(Lam: np.ndarray, R: np.ndarray, r: int) -> np.ndarray:
    """Rank-r action basis: top-r eigenvectors of the model's static
    observation information C = Lam' R^{-1} Lam — the directions the data
    is most informative about, per the computation-aware policy of arXiv
    2405.08971.  Every downstream formula is a V...V' sandwich, so the
    eigh sign/permutation ambiguity is inert, and ANY full-rank V at r=k
    reproduces the exact filter."""
    C = _sym((Lam * (1.0 / R)[:, None]).T @ Lam)
    _, vecs = np.linalg.eigh(C)           # ascending eigenvalues
    return vecs[:, ::-1][:, :r]


def _chol_solve_np(L: np.ndarray, B: np.ndarray) -> np.ndarray:
    return np.linalg.solve(L.T, np.linalg.solve(L, B))


def kalman_filter_lowrank(Y: np.ndarray, p: SSMParams,
                          mask: Optional[np.ndarray] = None,
                          rank: int = 0) -> KalmanResult:
    """Rank-r computation-aware filter (arXiv 2405.08971, downdate form).

    Projects the information-form update onto r fixed observation-space
    actions Z = R^{-1} Lam V (V from ``_lowrank_basis``), which reduces
    entirely to k-space: the update conditions EXACTLY on the r projected
    observations V'(Lam' R^{-1} y), so P_filt is the true posterior
    covariance of that coarsened problem — PSD by construction and
    CONSERVATIVE (P_filt^lowrank >= P_filt^exact in the PSD order), which
    is what keeps the uncertainty bands calibrated rather than
    overconfident.  At r=k the update is algebraically the exact
    information filter (gain P C (I + PC)^{-1}... identities).  Per-step
    cost: no k-sized factorization — one r x r Cholesky plus k x r
    matmuls (+ the 2 k^3 predict matmuls).

    The golden f64 oracle for ``dfm_tpu.ssm.lowrank_filter``.
    """
    Y = np.asarray(Y, dtype=np.float64)
    T, N = Y.shape
    k = p.n_factors
    Lam, A, Q, R = (np.asarray(p.Lam, np.float64), np.asarray(p.A, np.float64),
                    np.asarray(p.Q, np.float64), np.asarray(p.R, np.float64))
    r = resolve_rank(k, rank)
    V = _lowrank_basis(Lam, R, r)
    Rinv = 1.0 / R
    logR = np.log(R)
    G = Lam * Rinv[:, None]
    eps = 1e-10
    I_r = np.eye(r)
    if mask is None:
        B = Y @ G
        C_static = Lam.T @ G
        J_static = C_static @ V                    # (k, r)
        Gam_static = _sym(V.T @ J_static) + eps * I_r
        Lgam_static = np.linalg.cholesky(Gam_static)
        n_t_all = np.full(T, float(N))
        ldR_all = np.full(T, logR.sum())
    else:
        W = np.asarray(mask, dtype=np.float64)
        Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)
        Y = Yz
        B = Yz @ G
        n_t_all = W.sum(axis=1)
        ldR_all = W @ logR

    x_pred = np.zeros((T, k))
    P_pred = np.zeros((T, k, k))
    x_filt = np.zeros((T, k))
    P_filt = np.zeros((T, k, k))
    logdetG = np.zeros(T)
    corr = np.zeros(T)
    x, P = np.asarray(p.mu0, np.float64), np.asarray(p.P0, np.float64)
    for t in range(T):
        if t > 0:
            x = A @ x_filt[t - 1]
            P = _sym(A @ P_filt[t - 1] @ A.T + Q)
        x_pred[t] = x
        P_pred[t] = P
        if mask is None:
            C, J, Gam, Lgam = C_static, J_static, Gam_static, Lgam_static
        else:
            C = (Lam * (W[t] * Rinv)[:, None]).T @ Lam
            J = C @ V
            Gam = _sym(V.T @ J) + eps * I_r
            Lgam = np.linalg.cholesky(Gam)
        PJ = P @ J                                  # (k, r)
        # S = Z' (Lam P Lam' + R) Z pushed to k-space: J'PJ + V'CV.  The
        # SAME eps rides both S and Gam, so a fully-masked step (C = 0)
        # gives logdetG = 0 and an inert update exactly.
        S = _sym(J.T @ PJ) + Gam
        Ls = np.linalg.cholesky(S)
        u = B[t] - C @ x
        z = V.T @ u
        alpha = _chol_solve_np(Ls, z)
        x = x + PJ @ alpha
        P = _sym(P - PJ @ _chol_solve_np(Ls, PJ.T))   # rank-r downdate
        x_filt[t] = x
        P_filt[t] = P
        logdetG[t] = 2.0 * (np.sum(np.log(np.diag(Ls)))
                            - np.sum(np.log(np.diag(Lgam))))
        # Consistent quad correction z'(Gam^{-1} - S^{-1})z — the
        # quadratic of the SAME approximating Gaussian the determinant
        # above belongs to (see ssm.lowrank_filter's module docstring);
        # >= 0 always, exactly the full Woodbury term at r = k.
        corr[t] = float(z @ _chol_solve_np(Lgam, z) - z @ alpha)
    # Residual-pass quadratic — identical assembly to the info filter;
    # with the subspace correction this is the exact log-likelihood of
    # the rank-r approximating predictive model (and the exact data
    # log-likelihood at r=k).
    Vres = Y - x_pred @ Lam.T
    if mask is not None:
        Vres = W * Vres
    VR = Vres * Rinv[None, :]
    quad_R = np.einsum("tn,tn->t", Vres, VR)
    quad = quad_R - corr
    log2pi = np.log(2.0 * np.pi)
    loglik = float(np.sum(-0.5 * (n_t_all * log2pi + ldR_all + logdetG
                                  + quad)))
    return KalmanResult(x_pred, P_pred, x_filt, P_filt, loglik)


def rts_smoother_lowrank(kf: KalmanResult, p: SSMParams,
                         rank: int = 0) -> SmootherResult:
    """Rank-r RTS smoother: the backward gain's P_pred^{-1} is replaced by
    its projection V (V' P_pred V)^{-1} V' onto the same rank-r action
    basis as the filter, so each backward step is one r x r Cholesky plus
    k x r matmuls instead of a k x k solve.  Exact at r=k (V orthonormal:
    V Sigma^{-1} V' = P_pred^{-1}).  Lag-one covariances follow the same
    factored identity P_lag[t] = (P_sm[t] V) Sigma^{-1} G1' used by the
    exact smoother's P_sm[t] J[t-1]'.
    """
    T, k = kf.x_filt.shape
    A = np.asarray(p.A, np.float64)
    Lam = np.asarray(p.Lam, np.float64)
    R = np.asarray(p.R, np.float64)
    r = resolve_rank(k, rank)
    V = _lowrank_basis(Lam, R, r)
    AV = A.T @ V                                   # (k, r)
    eps = 1e-10
    I_r = np.eye(r)
    x_sm = np.zeros((T, k))
    P_sm = np.zeros((T, k, k))
    P_lag = np.zeros((T, k, k))
    G1 = np.zeros((T, k, r))                       # defined for t < T-1
    Lsig = np.zeros((T, r, r))

    x_sm[-1] = kf.x_filt[-1]
    P_sm[-1] = kf.P_filt[-1]
    for t in range(T - 2, -1, -1):
        Pp = kf.P_pred[t + 1]
        Lsig[t] = np.linalg.cholesky(_sym(V.T @ Pp @ V) + eps * I_r)
        G1[t] = kf.P_filt[t] @ AV
        a = _chol_solve_np(Lsig[t], V.T @ (x_sm[t + 1] - kf.x_pred[t + 1]))
        x_sm[t] = kf.x_filt[t] + G1[t] @ a
        E = V.T @ (P_sm[t + 1] - Pp) @ V
        S = _chol_solve_np(Lsig[t], _chol_solve_np(Lsig[t], E).T).T
        P_sm[t] = _sym(kf.P_filt[t] + G1[t] @ _sym(S) @ G1[t].T)
    for t in range(1, T):
        PV = P_sm[t] @ V
        P_lag[t] = _chol_solve_np(Lsig[t - 1], PV.T).T @ G1[t - 1].T
    return SmootherResult(x_sm, P_sm, P_lag)


def rts_smoother(kf: KalmanResult, p: SSMParams) -> SmootherResult:
    """Rauch-Tung-Striebel backward smoother with lag-one covariances.

    Lag-one smoothed covariance uses the exact identity
        Cov(f_t, f_{t-1} | Y) = P_sm[t] @ J_{t-1}'
    with J_t = P_filt[t] A' P_pred[t+1]^{-1}, which follows from the RTS
    conditional  f_t | f_{t+1}, y_1..t  (equivalent to the Shumway-Stoffer
    recursion; verified against a brute-force joint-Gaussian oracle in tests).
    """
    T, k = kf.x_filt.shape
    A = np.asarray(p.A, np.float64)
    x_sm = np.zeros((T, k))
    P_sm = np.zeros((T, k, k))
    P_lag = np.zeros((T, k, k))
    J = np.zeros((T, k, k))  # J[t] defined for t < T-1

    x_sm[-1] = kf.x_filt[-1]
    P_sm[-1] = kf.P_filt[-1]
    for t in range(T - 2, -1, -1):
        Pp = kf.P_pred[t + 1]
        # J_t = P_filt[t] A' P_pred[t+1]^{-1}  via solve on the symmetric Pp
        J[t] = np.linalg.solve(Pp, A @ kf.P_filt[t]).T
        x_sm[t] = kf.x_filt[t] + J[t] @ (x_sm[t + 1] - kf.x_pred[t + 1])
        P_sm[t] = _sym(kf.P_filt[t]
                       + J[t] @ (P_sm[t + 1] - Pp) @ J[t].T)
    for t in range(1, T):
        P_lag[t] = P_sm[t] @ J[t - 1].T
    return SmootherResult(x_sm, P_sm, P_lag)


def smoothed_moments(sm: SmootherResult):
    """Sufficient statistics for the EM M-step (SURVEY.md section 3.1).

    Purely a function of the smoother output; observation-side (masked) sums
    are formed in ``em_step`` where the data lives.

    Returns dict with:
      S_ff     = sum_t E[f_t f_t']                     (k, k)
      S_ff_lag = sum_{t>=1} E[f_{t-1} f_{t-1}']        (k, k)
      S_ff_cur = sum_{t>=1} E[f_t f_t']                (k, k)
      S_cross  = sum_{t>=1} E[f_t f_{t-1}']            (k, k)
      Ef       = smoothed means (T, k)
      EffT     = per-t second moments (T, k, k)
    """
    x, P, Pl = sm.x_sm, sm.P_sm, sm.P_lag
    EffT = P + np.einsum("ti,tj->tij", x, x)
    cross = Pl[1:] + np.einsum("ti,tj->tij", x[1:], x[:-1])
    return {
        "S_ff": EffT.sum(0),
        "S_ff_lag": EffT[:-1].sum(0),
        "S_ff_cur": EffT[1:].sum(0),
        "S_cross": cross.sum(0),
        "Ef": x,
        "EffT": EffT,
    }


def em_step(Y: np.ndarray, p: SSMParams,
            mask: Optional[np.ndarray] = None,
            estimate_A: bool = True,
            estimate_Q: bool = True,
            estimate_init: bool = False,
            r_floor: float = 1e-6,
            filter: str = "dense", rank: int = 0):
    """One EM iteration: E-step (filter+smoother) then closed-form M-step.

    Returns (new_params, loglik_of_old_params, smoother_result).

    M-step (BASELINE.json:5 "sufficient-statistic reductions"):
      Lam <- S_yf S_ff^{-1}          (per-series rows when masked)
      R   <- diag(sum_t y y' - Lam S_yf') / T   (masked: per-series count)
      A   <- S_cross S_ff_lag^{-1}
      Q   <- (S_ff_cur - A S_cross') / (T-1)

    With a mask, Lam_i / R_i use only series i's observed times
    (Banbura-Modugno): Lam_i = (sum_t w_ti y_ti Ef_t') (sum_t w_ti EffT_t)^{-1}
    and R_i includes the filtered-uncertainty correction lam_i' V_t lam_i.
    """
    Y = np.asarray(Y, dtype=np.float64)
    T, N = Y.shape
    if filter == "lowrank":
        kf = kalman_filter_lowrank(Y, p, mask=mask, rank=rank)
        sm = rts_smoother_lowrank(kf, p, rank=rank)
    else:
        ff = {"dense": kalman_filter, "info": kalman_filter_info}[filter]
        kf = ff(Y, p, mask=mask)
        sm = rts_smoother(kf, p)
    mom = smoothed_moments(sm)
    Ef, EffT = mom["Ef"], mom["EffT"]

    new = p.copy()
    if mask is None:
        S_yf = Y.T @ Ef                        # (N, k)
        Lam = np.linalg.solve(mom["S_ff"].T, S_yf.T).T
        R = (np.einsum("ti,ti->i", Y, Y) - np.einsum("ik,ik->i", Lam, S_yf)) / T
    else:
        W = np.asarray(mask, dtype=np.float64)
        Yz = np.where(W > 0, np.nan_to_num(Y), 0.0)
        # Per-series masked normal equations, vectorized over i.
        S_yf_i = np.einsum("ti,tk->ik", Yz, Ef)                # (N, k)
        S_ff_i = np.einsum("ti,tkl->ikl", W, EffT)             # (N, k, k)
        # A series with no observed entries has S_ff_i = 0; substitute the
        # identity so the batched solve stays nonsingular (its loading comes
        # out zero since S_yf_i is zero there too).
        k = p.n_factors
        never_obs = W.sum(0) == 0
        S_ff_i = np.where(never_obs[:, None, None], np.eye(k)[None], S_ff_i)
        Lam = np.linalg.solve(np.swapaxes(S_ff_i, 1, 2),
                              S_yf_i[:, :, None])[:, :, 0]
        counts = np.maximum(W.sum(0), 1.0)
        resid_sq = np.einsum("ti,ti->i", W, (Yz - Ef @ Lam.T) ** 2)
        smear = np.einsum("ik,ikl,il->i",
                          Lam, np.einsum("ti,tkl->ikl", W, sm.P_sm), Lam)
        R = (resid_sq + smear) / counts
    new.Lam = Lam
    new.R = np.maximum(R, r_floor)

    if estimate_A:
        A = np.linalg.solve(mom["S_ff_lag"].T, mom["S_cross"].T).T
        new.A = A
        if estimate_Q:
            Q = (mom["S_ff_cur"] - A @ mom["S_cross"].T) / (T - 1)
            new.Q = _sym(Q)
    elif estimate_Q:
        # A fixed (e.g. zero for static DFM): Q <- mean E[eta eta'].
        A = p.A
        Q = (mom["S_ff_cur"] - A @ mom["S_cross"].T - mom["S_cross"] @ A.T
             + A @ mom["S_ff_lag"] @ A.T) / (T - 1)
        new.Q = _sym(Q)
    if estimate_init:
        new.mu0 = sm.x_sm[0]
        new.P0 = _sym(sm.P_sm[0])
    return new, kf.loglik, sm


def em_fit(Y: np.ndarray, p0: SSMParams,
           mask: Optional[np.ndarray] = None,
           max_iters: int = 50, tol: float = 1e-6,
           estimate_A: bool = True, estimate_Q: bool = True,
           estimate_init: bool = False,
           callback=None, filter: str = "dense", rank: int = 0):
    """EM driver with relative-loglik convergence (SURVEY.md section 3.1).

    Returns (params, logliks, converged) where logliks[i] is the
    log-likelihood *at the parameters entering iteration i* — monotone
    non-decreasing by the EM invariant (SURVEY.md section 4.2.2a).
    """
    p = p0.copy()
    logliks = []
    converged = False
    for it in range(max_iters):
        p_new, ll, _ = em_step(Y, p, mask=mask, estimate_A=estimate_A,
                               estimate_Q=estimate_Q,
                               estimate_init=estimate_init, filter=filter,
                               rank=rank)
        logliks.append(ll)
        if callback is not None:
            callback(it, ll, p)
        p = p_new
        if it > 0:
            denom = max(abs(logliks[-2]), 1e-12)
            if (ll - logliks[-2]) / denom < tol:
                converged = True
                break
    return p, np.array(logliks), converged


def pca_init(Y: np.ndarray, k: int, static: bool = False,
             mask: Optional[np.ndarray] = None) -> SSMParams:
    """Stock-Watson principal-components initializer (SURVEY.md R3).

    Assumes ``Y`` is already standardized per series (mean 0 — the state-space
    model has no intercept; the ``api`` layer owns standardization, reference
    component R2).  Lam_hat = sqrt(N) * top-k right singular vectors of the raw
    data matrix (= eigvecs of Y'Y); f_hat = Y Lam_hat / N.  Then A, Q from an
    OLS VAR(1) on f_hat and R from idiosyncratic residual variances.  With
    ``static`` the dynamics are pinned to A=0, Q=I (factor scale absorbed into
    Lam).  Missing entries (mask=0 or NaN) are zero-filled — the standard EM
    warm start for incomplete *standardized* panels (zero = series mean).
    """
    Y = np.asarray(Y, dtype=np.float64)
    T, N = Y.shape
    if mask is not None:
        Y = np.where(np.asarray(mask) > 0, np.nan_to_num(Y), 0.0)
    # SVD of the data matrix avoids forming the N x N covariance.
    U, s, Vt = np.linalg.svd(Y, full_matrices=False)
    V = Vt[:k].T                                  # (N, k) top eigvecs of Y'Y
    Lam = np.sqrt(N) * V
    F = Y @ Lam / N                               # (T, k)
    resid = Y - F @ Lam.T
    R = np.maximum(resid.var(axis=0), 1e-6)
    A, Q, mu0, P0 = var_tail(F, k, static)
    return SSMParams(Lam, A, Q, R, mu0, P0)


def var_tail(F: np.ndarray, k: int, static: bool = False):
    """The k-sized dynamics tail of the PCA init: OLS VAR(1) on the factor
    path + stationary P0.  Shared with the device-side initializer
    (``estim.init.pca_init_device``) — the factor path is tiny, so this
    always runs on host."""
    F = np.asarray(F, np.float64)
    if static:
        A = np.zeros((k, k))
        Q = np.eye(k)
    else:
        X, Z = F[1:], F[:-1]
        A = np.linalg.solve(Z.T @ Z + 1e-8 * np.eye(k), Z.T @ X).T
        eta = X - Z @ A.T
        Q = _sym(eta.T @ eta / max(len(eta) - 1, 1)) + 1e-8 * np.eye(k)
    mu0 = np.zeros(k)
    P0 = _solve_discrete_lyapunov_or_eye(A, Q)
    return A, Q, mu0, P0


def _solve_discrete_lyapunov_or_eye(A: np.ndarray, Q: np.ndarray) -> np.ndarray:
    """Stationary state covariance P = A P A' + Q, or I if A is not stable."""
    k = A.shape[0]
    eig = np.max(np.abs(np.linalg.eigvals(A))) if k else 0.0
    if eig >= 0.999:
        return np.eye(k)
    # vec(P) = (I - A kron A)^{-1} vec(Q)
    M = np.eye(k * k) - np.kron(A, A)
    P = np.linalg.solve(M, Q.reshape(-1)).reshape(k, k)
    return _sym(P)


def forecast(p: SSMParams, x_T: np.ndarray, P_T: np.ndarray, horizon: int):
    """h-step-ahead factor and observable forecasts (SURVEY.md section 3.2).

    Returns (f_fore (h, k), y_fore (h, N), P_fore (h, k, k)).
    """
    k = p.n_factors
    f = np.zeros((horizon, k))
    P = np.zeros((horizon, k, k))
    x, V = np.asarray(x_T, np.float64), np.asarray(P_T, np.float64)
    A, Q = np.asarray(p.A, np.float64), np.asarray(p.Q, np.float64)
    for h in range(horizon):
        x = A @ x
        V = _sym(A @ V @ A.T + Q)
        f[h] = x
        P[h] = V
    y = f @ np.asarray(p.Lam, np.float64).T
    return f, y, P
